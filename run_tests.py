#!/usr/bin/env python3
"""Lab-test CLI driver — the student-facing `run-tests.py`
(handout-files/run-tests.py:24-341) + `DSLabsTestCore.main`
(junit/DSLabsTestCore.java:116-284) re-designed as one entry point.

    python run_tests.py --lab 3                 # all lab 3 tests
    python run_tests.py --lab 1 --part 2 -n 3,5 # selection
    python run_tests.py --lab 2 --no-run        # search tests only
    python run_tests.py --lab 4 --checks        # conformance checks on
    python run_tests.py --replay-traces         # re-check traces/ saved traces

Flags map onto GlobalSettings the way the reference maps CLI flags to JVM
properties (`--checks` -> doChecks, `-s` -> saveTraces, ...).  Exit code 1
on any failure (DSLabsTestCore.java:282-284).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

LAB_TEST_MODULES = [
    "tests.test_lab0_run",
    "tests.test_lab0_search",
    "tests.test_lab1",
    "tests.test_lab2_viewserver",
    "tests.test_lab2_pb",
    "tests.test_lab3_paxos",
    "tests.test_lab4_shardmaster",
    "tests.test_lab4_shardstore",
]


def _discover() -> None:
    """Populate the registry by importing the lab test modules — the
    classpath-scan analog (utils/ClassSearch.java:35-89)."""
    import importlib

    for mod in LAB_TEST_MODULES:
        importlib.import_module(mod)


def _parse_args(argv):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--lab", "-l", help="lab to run (0-4)")
    p.add_argument("--part", "-p", type=int, help="part number")
    p.add_argument("--test-num", "-n",
                   help="comma-separated test numbers (e.g. 2,5,7)")
    p.add_argument("--no-run", "--exclude-run-tests", action="store_true",
                   dest="no_run", help="skip run tests")
    p.add_argument("--no-search", "--exclude-search-tests",
                   action="store_true", dest="no_search",
                   help="skip search tests")
    p.add_argument("--exclude-unreliable", action="store_true",
                   help="skip unreliable-network tests")
    p.add_argument("--checks", action="store_true",
                   help="enable conformance checks (determinism, "
                        "idempotence, clone consistency)")
    p.add_argument("--lint", action="store_true",
                   help="run the static protocol conformance linter "
                        "(dslabs_tpu/analysis, rules C1-C4) before the "
                        "selected labs; unwaived findings fail the run "
                        "(docs/analysis.md)")
    p.add_argument("--no-timeouts", action="store_true",
                   help="disable per-test timeouts")
    p.add_argument("--single-threaded", action="store_true",
                   help="single-threaded run states / searches")
    p.add_argument("-s", "--save-traces", action="store_true",
                   help="save violation traces to traces/")
    p.add_argument("-z", "--start-viz", action="store_true",
                   help="open the trace viewer on search-test failure")
    p.add_argument("-g", "--log-level", default=None, help="log level")
    p.add_argument("--search-backend", choices=("object", "tensor"),
                   default=None,
                   help="search strategy for search tests: the object "
                        "graph checker (default) or the TPU tensor "
                        "engine via protocol twins (SURVEY §8.1)")
    p.add_argument("--results-file", default=None,
                   help="write JSON results to this file")
    p.add_argument("--replay-traces", action="store_true",
                   help="re-check all saved traces in traces/")
    p.add_argument("--visualize-trace", metavar="TRACE",
                   help="open a saved trace in the trace viewer")
    p.add_argument("--debugger", nargs="*", metavar="ARG",
                   help="render a lab's initial system in the viewer: "
                        "--debugger <numServers> <numClients> <workload> "
                        "(with --lab); VizConfig analog")
    return p.parse_args(argv)


def _apply_flags(args) -> None:
    from dslabs_tpu.utils.flags import GlobalSettings

    if args.checks:
        GlobalSettings.do_checks = True
    if args.no_timeouts:
        GlobalSettings.test_timeouts_disabled = True
    if args.single_threaded:
        GlobalSettings.single_threaded = True
    if args.save_traces:
        GlobalSettings.save_traces = True
    if args.start_viz:
        GlobalSettings.start_viz = True
    if args.log_level:
        import logging

        GlobalSettings.log_level = args.log_level
        logging.basicConfig(level=args.log_level.upper())
    if args.search_backend:
        GlobalSettings.search_backend = args.search_backend
    if os.environ.get("DSLABS_FORCE_CPU"):
        # The axon accelerator plugin pins jax_platforms at import, so
        # the JAX_PLATFORMS env var alone cannot select CPU; re-pin via
        # config before any backend initialises (same trick as
        # tests/conftest.py and bench.py).  Lets the tensor backend run
        # the lab suites on a machine whose accelerator runtime is
        # wedged or absent.
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/jaxcache-cpu")


def _replay_traces() -> int:
    """CheckSavedTracesTest analog (junit/CheckSavedTracesTest.java:44-108):
    one check per saved trace, replaying its history under its invariants."""
    from dslabs_tpu.search.replay import replay_trace
    from dslabs_tpu.search.results import EndCondition
    from dslabs_tpu.search.settings import SearchSettings
    from dslabs_tpu.search.trace import SerializableTrace

    traces = SerializableTrace.traces()
    if not traces:
        print("No saved traces found in traces/")
        return 0
    failures = 0
    for t in traces:
        settings = SearchSettings()
        for inv in t.invariants:
            settings.add_invariant(inv)
        results = replay_trace(t.initial_state(), t.history, settings)
        ok = results.end_condition not in (
            EndCondition.INVARIANT_VIOLATED, EndCondition.EXCEPTION_THROWN)
        print(f"{'PASS' if ok else 'FAIL'}  {t!r}")
        if not ok:
            failures += 1
            state = (results.invariant_violating_state
                     or results.exceptional_state)
            if state is not None:
                state.print_trace()
    print(f"\n{len(traces) - failures}/{len(traces)} saved traces pass")
    return 1 if failures else 0


def _debugger(lab, dbg_args) -> int:
    """VizClient.main analog (VizClient.java:39-102): build a lab's
    initial state from CLI args and serve the interactive
    branch-exploring debugger over it (DebuggerWindow.java:89)."""
    from dslabs_tpu.viz import viz_configs
    from dslabs_tpu.viz.debugger import serve_debugger

    configs = viz_configs()
    if lab is None or str(lab) not in configs:
        print(f"No viz config for lab {lab!r}; available: "
              f"{sorted(configs)}")
        return 1
    state = configs[str(lab)](list(dbg_args))
    serve_debugger(state)
    return 0


def _visualize_trace(path: str) -> int:
    """SavedTraceViz analog: render the static HTML step viewer AND serve
    the interactive debugger preloaded with the trace's event path, so
    the user can deviate at any step and explore successor branches
    (EventTreeState.java:47-209)."""
    from dslabs_tpu.search.trace import SerializableTrace
    from dslabs_tpu.viz.debugger import serve_debugger
    from dslabs_tpu.viz.server import render_trace_html

    trace = SerializableTrace.load(path)
    if trace is None:
        print(f"Could not load trace {path}")
        return 1
    out_path = path + ".html"
    with open(out_path, "w") as f:
        f.write(render_trace_html(trace))
    print(f"Static trace view: {out_path} ({len(trace.history)} events)")
    serve_debugger(trace.initial_state(), preload_events=trace.history)
    return 0


def main(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    # Object-backend runs keep any transitive jax import off the
    # accelerator (the bench owns the real chip); the tensor backend —
    # via flag or DSLABS_SEARCH_BACKEND — runs search tests ON it.  Must
    # happen before _discover() imports anything jax-flavoured.
    backend = args.search_backend or os.environ.get(
        "DSLABS_SEARCH_BACKEND", "object")
    if backend != "tensor":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _apply_flags(args)

    if args.lint:
        # The static half of --checks (ISSUE 10): the runtime checks
        # catch a mutation when a run happens to hit it; the linter
        # catches the pattern before any search runs.  Findings gate
        # the labs — a protocol that fails conformance would produce
        # untrustworthy verdicts anyway.
        from dslabs_tpu import analysis

        findings = analysis.run_conformance()
        print(analysis.render_findings(findings,
                                       header="conformance lint"))
        if any(not f.waived for f in findings):
            return 1

    if args.replay_traces:
        return _replay_traces()
    if args.visualize_trace:
        return _visualize_trace(args.visualize_trace)
    if args.debugger is not None:
        return _debugger(args.lab, args.debugger)

    from dslabs_tpu.harness import registry, run_tests, select_tests

    _discover()
    nums = None
    if args.test_num:
        nums = [int(x) for x in args.test_num.split(",") if x.strip()]
    selected = select_tests(
        registry(), lab=args.lab, part=args.part, nums=nums,
        exclude_run=args.no_run, exclude_search=args.no_search,
        exclude_unreliable=args.exclude_unreliable)
    if not selected:
        print("No tests matched the selection")
        return 1
    report = run_tests(selected, results_output_file=args.results_file)
    return 0 if report.all_passed else 1


if __name__ == "__main__":
    sys.exit(main())
