# Convenience targets mirroring the reference's Makefile/run-tests entry
# points (there is no build step: the framework is pure Python + JAX).

PY ?= python

.PHONY: test test-fast lint analysis-smoke perf-smoke fault-smoke swarm-smoke capacity-smoke capacity2-smoke obs-smoke chaos-smoke service-smoke trace-smoke mesh-smoke lanes-smoke memo-smoke scenario-smoke spec-smoke lab0 lab1 lab2 lab3 lab4 bench dryrun handout clean

test:            ## full acceptance + parity suite
	$(PY) -m pytest tests/ -q

test-fast:       ## skip the slowest files (TPU-engine parity compiles)
	$(PY) -m pytest tests/ -q --ignore=tests/test_tpu_engine.py \
	    --ignore=tests/test_tpu_sharded.py --ignore=tests/test_tpu_lab4.py

lab0 lab1 lab2 lab3 lab4:   ## scored lab runs via the CLI driver
	$(PY) run_tests.py --lab $(subst lab,,$@)

# lint = the soundness sanitizer's full pass (ISSUE 10): the protocol
# conformance linter (C1-C4 over specs/protocols/adapters/labs + the
# ProtocolSpec compile gate) AND the jaxpr hot-path auditor (J0-J5
# over the lowered dispatch-site programs of the pingpong engines on a
# virtual CPU mesh, retrace check included).  Exit 1 on any unwaived
# finding; .sanitizer-waivers documents the justified exceptions.
# docs/analysis.md is the field guide.
lint:            ## soundness sanitizer: conformance linter + jaxpr auditor
	$(PY) -m dslabs_tpu.analysis all

# analysis-smoke = the sanitizer's own test suite (tests/test_analysis.py):
# one deliberately-violating red fixture per rule asserting the exact
# finding code (C1-C4, J0-J5), the clean-pass pin on every shipped
# protocol, the jaxpr zero-findings pin on the pingpong superstep +
# promote for BOTH engines, SpecError compile-gate shapes, waiver-file
# handling, and the CLI rc contract — then the CLI itself end to end.
analysis-smoke:  ## sanitizer suite (red fixtures per rule + shipped-tree clean pin) on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m analysis -p no:cacheprovider
	$(PY) -m dslabs_tpu.analysis all

bench:           ## TPU states/min benchmark (one JSON line)
	$(PY) bench.py

# perf-smoke = the BASELINE.json states/min floor PLUS the dry-run
# 8-virtual-device superstep-vs-legacy parity gate (exact unique/
# explored/verdict match on pingpong + paxos d5 + shardstore —
# tests/test_superstep.py, ISSUE 3 acceptance).
perf-smoke:      ## fast CPU perf gate vs the BASELINE.json floor
	$(PY) -m pytest tests/ -q -m perf -s -p no:cacheprovider

# fault-smoke = the full injected-fault recovery suite: the in-process
# retry/failover/resume/watchdog paths (tests/test_supervisor.py) PLUS
# the process-isolation warden's deterministic kill/hang/crash matrix
# (tests/test_warden.py — child SIGKILLed mid-search resumes from the
# checkpoint, a hung child is reaped within its heartbeat grace,
# exit-code classification pinned, .prev-rotation torn-write recovery).
# Tier-1 keeps only the FAST warden tests (spawn-light, no accelerator);
# the slowest spawn-heavy variants are additionally marked `slow` and
# run only here.
fault-smoke:     ## injected-fault recovery suite (retry/failover/resume/watchdog/warden) on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m fault -p no:cacheprovider

# swarm-smoke = the whole swarm-explorer suite (tests/test_swarm.py)
# INCLUDING the deep-narrow paxos/lab4 scenarios that tier-1 skips
# (marked slow+perf): determinism, verdict parity, dedup sharing,
# frontier-seeding resume parity, dispatch-seam fault injection, loud
# overflow accounting, and the portfolio acceptance (BFS alone
# TIME_EXHAUSTED vs portfolio violation with a minimized,
# replay-verified witness).
swarm-smoke:     ## swarm explorer suite incl. slow deep-narrow scenarios, on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_swarm.py -q -p no:cacheprovider

# capacity-smoke = the host-RAM spill-tier suite (tests/test_spill.py):
# strict DEPTH_EXHAUSTED exact unique/explored parity with the device
# visited table capped at ~1/8 of the state count (single-device AND
# sharded engines), SIGKILL-mid-spill resume parity, the supervisor's
# CapacityOverflow->spill-retry capacity ladder, spill-dispatch fault
# injection, and the foreign-checkpoint refusal — plus the bench's
# `--spill` phase shape (states/min at 1/8 capacity vs uncapped) via
# `python bench.py --spill` if you want the number itself.
capacity-smoke:  ## host-RAM spill tier + capacity-ladder suite on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m capacity -p no:cacheprovider

# capacity2-smoke = capacity round 2 (ISSUE 15, tpu/packing.py +
# tpu/symmetry.py + the async spill gear): packed-vs-unpacked EXACT
# parity on pingpong + lab1 (strict and beam, device + host loops +
# sharded), the >= 2x bytes_per_state pins on the lab1/paxos specs and
# the packed-capacity depth test (a frontier sized in packed bytes
# completes a depth the unpacked layout provably cannot fit),
# SIGKILL-mid-run packed-checkpoint resume + the loud packed<->raw
# cross-resume conversion/refusal, the symmetry-reduced paxos quotient
# (pinned canonical counts, verdict parity, replay-verified witness),
# and the async drain's exactness + overlap accounting — PLUS the
# packed end-to-end leg of tools/obs_smoke.py (STATUS capacity block +
# the ledger's capacity:bytes_per_state guard rc 0/1 both ways).
capacity2-smoke: ## capacity round 2: packed encoding + symmetry reduction + async spill, on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m capacity2 -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) tools/obs_smoke.py

# obs-smoke = the unified telemetry suite (tests/test_telemetry.py):
# span-count == dispatch-count on both engines, the zero-added-
# dispatches/transfers overhead guard (per-device lanes + STATUS.json
# writer enabled), per-device skew lanes on the 8-device mesh, SIGKILL
# flight-log survival with the in-flight dispatch named, the
# report-CLI golden sections + --json schema pin, the live-monitor
# watch view, the bench-ledger compare, supervisor retry/failover
# event plumbing, and the bench-JSON schema pin for the `telemetry`
# block + error-with-spans shape (the slow bench run tier-1 skips) —
# PLUS the CLI end-to-end steps via tools/obs_smoke.py: `telemetry
# watch --once` on a finished run and `telemetry compare` on a parity
# ledger and an injected-regression ledger.  docs/observability.md is
# the field guide.
obs-smoke:       ## unified telemetry suite (flight recorder / metrics / reports / watch / ledger) on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m obs -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) tools/obs_smoke.py

# chaos-smoke = the elastic-mesh resilience suite (tests/test_chaos.py):
# the degraded-mesh width ladder sharded(D)->sharded(D/2)->...->device->
# host with exact cross-width resume parity (8->4->2->1 on the CPU
# dryrun mesh, strict pingpong + lab1, SIGKILL-mid-level warden
# variant), the adaptive OOM knob-shrink re-level, and the seeded chaos
# soak (>= 20 deterministic faults across >= 3 dispatch sites, exact
# fault-free parity asserted) — plus the long soak variants tier-1
# skips (marked slow).  `python -m dslabs_tpu.tpu.chaos` is the by-hand
# entry point.
chaos-smoke:     ## elastic-mesh resilience suite (degraded ladder / knob shrink / seeded chaos soak) on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m chaos -p no:cacheprovider

# service-smoke = the multi-tenant checking-service suite
# (tests/test_service.py): the unified child-death taxonomy table
# (warden exit codes + stderr OOM markers, agreeing with
# supervisor.classify_oom), the structured queue-full retry-after
# rejection (never raises, never blocks), journal torn-tail replay +
# tmp/replace compaction, DRR fairness + per-tenant quotas, the
# CPU-pinned conformance admission gate rejecting an unsound spec with
# SpecError-derived findings BEFORE any twin compiles, and the
# tenant-isolation chaos soak (3 tenants, seeded oom/hang/crash fault
# schedule on one tenant: neighbors' verdicts bit-exact vs solo
# baselines, the victim degraded-but-sound or structured-failed) —
# then the `python -m dslabs_tpu.service` CLI end to end.
# docs/service.md is the field guide.
service-smoke:   ## multi-tenant checking service suite (queue / admission / fairness / isolation soak) on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m service -p no:cacheprovider

# trace-smoke = the end-to-end causal-tracing + cost-accounting suite
# (tests/test_tracing.py, ISSUE 13): trace-ID propagation
# submit -> journal -> scheduler -> warden env -> child flight logs,
# the SIGKILL acceptance (one pingpong job submitted to a local
# server, its warden child SIGKILLed mid-level, `telemetry trace`
# still renders the full causal chain from disk alone and names the
# in-flight dispatch), per-tenant COSTS.jsonl sums agreeing with the
# jobs' SearchOutcome counters exactly, torn SERVER_STATUS/COSTS
# reads, the run-dir retention sweep, and the compile-creep /
# cost-per-unique ledger-compare guards — then the trace-assembler
# leg of tools/obs_smoke.py (the CLI end to end).
# docs/observability.md "Tracing a job end-to-end" is the field guide.
trace-smoke:     ## causal tracing + cost-ledger suite (assembler / COSTS / retention) on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m trace -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) tools/obs_smoke.py

# mesh-smoke = the owner-sharded multi-chip superstep suite
# (tests/test_mesh_exchange.py, ISSUE 12): the width-parity matrix —
# exact unique/explored/verdict parity between the fused in-superstep
# row exchange and the legacy promote-boundary driver at n_devices in
# {1, 2, 4, 8} on pingpong + lab1 — the <= 2 dispatches/level budget
# pin with a zero-collective promote lowering, Pallas-vs-jnp
# visited-table bit-exact parity (incl. the table-full overflow
# contract) standalone AND through a full sharded search, the
# cross-width checkpoint resume chain 8->4->2->1, first-class carry
# placement (partition rules -> NamedSharding everywhere), and the
# bench --mesh phase schema — all on the CPU virtual 8-device mesh, no
# TPU hardware needed.  ISSUE 18 adds the packed-wire suite
# (tests/test_mesh_packing.py): packed-vs-raw exchange parity across
# widths {1,2,4,8} + the >= 8x wire bytes-per-state floor, the
# delta-lane (varint) pb parity, cross-width resume through the packed
# checkpoint format, the root-fanout/work-stealing imbalance
# acceptance, packed-spill parity at 1/8 capacity, the
# pack/decode/steal dispatch-site audits, and the mesh_unpacked /
# skew_agg observability pins.  docs/perf.md "mesh dispatch model" +
# "The wire format" are the field guides.
mesh-smoke:      ## owner-sharded superstep width-parity + packed-wire/steal suite on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m mesh -p no:cacheprovider

# lanes-smoke = the batched-job-lanes suite (tests/test_lanes.py,
# ISSUE 14): lane-vs-solo EXACT parity (unique/explored/verdict
# bit-identical at L in {1, 2, 4}, pingpong + lab1, strict + beam +
# mixed per-lane depth limits), continuous-batching swap-in parity
# with zero recompiles, the dispatches-per-job amortisation pin
# (4-lane batch <= 0.5x the 4-solo dispatch count), SIGKILL-mid-batch
# per-lane checkpoint resume through the LaneBatchWarden child,
# poisoned-lane eviction leaving neighbors bit-exact, per-tenant
# COSTS sums across a batched drain == the solo drain's, the lane
# compare guards, and the solo-path overhead guard (lanes off = solo
# dispatch/device_get counts untouched) — all CPU, no TPU needed.
# PLUS the lanes leg of tools/obs_smoke.py (bench phase schema +
# compare guards end-to-end).  docs/service.md "Batched job lanes"
# is the field guide.
lanes-smoke:     ## batched job lanes: parity matrix + continuous batching + resume + cost split on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m lanes -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) tools/obs_smoke.py

# memo-smoke = the cross-job memoization suite (tests/test_memo.py,
# ISSUE 16): structural-fingerprint identity (rename-only resubmits
# hit, one-handler edits miss), visited-tier save/load with loud
# pack/symmetry refusals, the exact-key verdict-cache hit (zero
# dispatches, journaled memo_hit, ~0 COSTS device_secs), warm-start
# and incremental re-check exact parity vs cold runs (incl. the
# strict/beam x packed on/off sweep and SIGKILL-mid-warm-start
# resume), stale-verdict impossibility, the 3-tenant <10% resubmit
# billing pin, and the memo-off overhead guard — all CPU.  PLUS the
# memo leg of tools/obs_smoke.py (bench --memo schema + the
# memo:hit_rate compare guard rc 0/1 both ways).  docs/memo.md is
# the field guide.
memo-smoke:      ## cross-job memoization: verdict cache + warm start + incremental re-check parity on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m memo -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) tools/obs_smoke.py

# scenario-smoke = the checkable-fault-scenario suite
# (tests/test_scenarios.py, ISSUE 19): fault-free parity / overhead
# guard on both engines (zero-budget FaultModel == plain spec,
# exactly), the paxos partition-then-heal safety pins and the
# broken-quorum witness that NAMES its HEAL event, crash
# durable-vs-volatile semantics on _step_one, fault lanes through
# packing/symmetry/spill/checkpoint (incl. SIGKILL-mid-scenario
# resume and the fault-signature fingerprint refusal), the C6
# conformance fixtures, telemetry/warden counter wiring, and the
# partitioned-scenario chaos-soak leg.  docs/scenarios.md is the
# field guide.
scenario-smoke:  ## checkable fault scenarios: partition/crash/drop-dup model events + witness replay on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m scenario -p no:cacheprovider

# spec-smoke = the replicated-protocol spec layer (ISSUE 20): the
# generated lab3/lab4 twins vs the retired hand twins
# (tests/fixtures/hand_twins/) as parity oracles, the slot/quorum
# compile gates, and the packed slot-lane roundtrips.
spec-smoke:      ## replicated-protocol spec layer: generated-vs-hand parity matrix + slot/quorum gates on CPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m spec -p no:cacheprovider

dryrun:          ## multi-chip sharding dry run on a virtual CPU mesh
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

handout:         ## student distribution (lab solutions AST-stripped)
	$(PY) tools/handout.py --out /tmp/dslabs_tpu_handout --tar

clean:
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null; true
	rm -rf .pytest_cache
